"""Streaming serving gateway suite (ISSUE 16).

Covers the acceptance criteria on the CPU backend:
- OpenAI-compatible `/v1/chat/completions` over a REAL socket, with the
  streamed deltas byte-identical to the non-streaming response (greedy
  determinism end to end through the committed-token seam);
- native `/v1/discussions` multi-knight streams with crash-consistent
  event ids (`turn:c0,c1,...` — one id is the whole multi-row
  watermark) and `Last-Event-ID` reconnects that lose and duplicate
  NOTHING;
- SLO-driven admission: shed with 429/503 + Retry-After +
  machine-readable reason at the inflight cap / drain gate, deadline
  propagation failing an already-spent budget fast (408, its own
  classified error kind, zero prefill consumed);
- `pause_admission(reason)` threading verbatim into SchedulerRefused
  and `describe()["admission"]`;
- the factored `resume_from_journal` library seam (`commands.serve`
  re-export identity) and post-restart stream restoration from the
  intent journal (reconnect ladder leg 2);
- the RT-GAUGE-LEAK contract on `roundtable_gateway_inflight_streams`
  and the describe()/SURFACE_BINDINGS drift bound;
- the kill -9 chaos acceptance (slow): 3 concurrent streams, SIGKILL,
  restart `--resume`, every client reconnects via Last-Event-ID with
  greedy token parity vs the uninterrupted run.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

jax = pytest.importorskip("jax")

from theroundtaible_tpu.core.errors import classify_error
from theroundtaible_tpu.engine import deadlines, faults
from theroundtaible_tpu.engine.engine import InferenceEngine
from theroundtaible_tpu.engine.models.registry import get_model_config
from theroundtaible_tpu.engine.scheduler import (DeadlineExpired,
                                                 SchedulerRefused,
                                                 SessionScheduler)
from theroundtaible_tpu.engine.session_journal import SessionJournal
from theroundtaible_tpu.gateway import Gateway
from theroundtaible_tpu.gateway.admission import AdmissionController
from theroundtaible_tpu.gateway.streams import (format_event_id,
                                                parse_event_id)
from theroundtaible_tpu.utils import telemetry

MODEL_KW = dict(max_seq_len=512)

PROMPT = ("The round table met at dawn to discuss the castle walls "
          "and the eastern gate.")
PROMPT2 = ("A different discussion entirely, about dragons and the "
           "kingdom's gold reserves.")


@pytest.fixture(autouse=True)
def clean_faults():
    faults.disarm()
    deadlines.reset_rungs()
    deadlines.disarm_watchdog()
    deadlines.clear_hang_log()
    deadlines.end_drain()
    yield
    faults.disarm()
    deadlines.reset_rungs()
    deadlines.disarm_watchdog()
    deadlines.clear_hang_log()
    deadlines.end_drain()


def make_engine(**kw):
    cfg = get_model_config("tiny-gemma", **MODEL_KW)
    kw.setdefault("num_slots", 8)
    return InferenceEngine(cfg, **kw)


@pytest.fixture(scope="module")
def shared_engine():
    return make_engine()


@pytest.fixture(scope="module")
def unit_engine():
    """A second engine for scheduler-level unit tests, so they never
    share slot capacity with the module gateway's live scheduler."""
    return make_engine()


@pytest.fixture(scope="module")
def gw(shared_engine, tmp_path_factory):
    jdir = tmp_path_factory.mktemp("gw-journal")
    sched = SessionScheduler(shared_engine,
                             journal=SessionJournal(jdir))
    g = Gateway(sched, port=0, intent_dir=str(jdir))
    g.start_in_thread()
    yield g
    g.stop()
    sched.close()


# ---------------------------------------------------------------------
# A minimal raw-socket HTTP/SSE client (http.client buffers SSE).
# ---------------------------------------------------------------------


class Conn:
    def __init__(self, port, method, path, body=None, headers=None,
                 timeout=120.0):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=timeout)
        payload = (json.dumps(body).encode("utf-8")
                   if body is not None else b"")
        head = (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
                f"Content-Length: {len(payload)}\r\n")
        for k, v in (headers or {}).items():
            head += f"{k}: {v}\r\n"
        self.sock.sendall(head.encode("latin-1") + b"\r\n" + payload)
        self.f = self.sock.makefile("rb")
        self.status = int(self.f.readline().split()[1])
        self.headers = {}
        while True:
            ln = self.f.readline().decode("latin-1").strip()
            if not ln:
                break
            k, _, v = ln.partition(":")
            self.headers[k.lower()] = v.strip()

    def events(self):
        """Yield (event_id, data_str) per SSE event until EOF."""
        eid, data = None, []
        for raw in self.f:
            ln = raw.decode("utf-8").rstrip("\n")
            if ln.startswith("id: "):
                eid = ln[4:]
            elif ln.startswith("data: "):
                data.append(ln[6:])
            elif ln.startswith(":"):
                continue
            elif ln == "" and data:
                yield eid, "\n".join(data)
                eid, data = None, []

    def body_json(self):
        n = int(self.headers.get("content-length", "0"))
        return json.loads(self.f.read(n).decode("utf-8")) if n else {}

    def close(self):
        try:
            self.f.close()
            self.sock.close()
        except OSError:
            pass


def read_stream(port, path, body=None, method="POST", headers=None):
    """Full native-stream read: returns (meta, token_events, terminal)
    where token_events is [(event_id, payload_dict), ...]."""
    c = Conn(port, method, path, body=body, headers=headers)
    assert c.status == 200, c.body_json()
    meta, toks, terminal = None, [], None
    for eid, data in c.events():
        ev = json.loads(data)
        if ev["type"] == "stream":
            meta = ev
        elif ev["type"] in ("tokens", "summary"):
            toks.append((eid, ev))
        else:
            terminal = ev
            break
    c.close()
    return meta, toks, terminal


def row_tokens(toks, rows):
    """Per-row concatenated token ids from a token-event list."""
    out = [[] for _ in range(rows)]
    for _eid, ev in toks:
        if ev["type"] == "tokens":
            out[ev["row"]].extend(ev["tokens"])
        else:  # summary
            for i, d in ev["rows"].items():
                out[int(i)].extend(d["tokens"])
    return out


# ---------------------------------------------------------------------
# chat completions
# ---------------------------------------------------------------------


@pytest.mark.gateway
class TestChatCompletions:
    def test_stream_matches_nonstream(self, gw):
        """Greedy determinism through the whole stack: the SSE deltas
        concatenate to exactly the non-streaming response for the same
        prompt (different sessions, same prefill)."""
        body = {"model": "lancelot", "max_tokens": 8,
                "messages": [{"role": "user", "content": PROMPT}]}
        c = Conn(gw.port, "POST", "/v1/chat/completions",
                 body=dict(body, session="chat-ns"))
        assert c.status == 200
        full = c.body_json()
        c.close()
        text = full["choices"][0]["message"]["content"]
        assert full["choices"][0]["finish_reason"] == "stop"
        assert full["usage"]["completion_tokens"] > 0

        c = Conn(gw.port, "POST", "/v1/chat/completions",
                 body=dict(body, session="chat-st", stream=True))
        assert c.status == 200
        assert c.headers["content-type"].startswith("text/event-stream")
        deltas, done, finish = [], False, None
        for _eid, data in c.events():
            if data == "[DONE]":
                done = True
                break
            chunk = json.loads(data)
            choice = chunk["choices"][0]
            deltas.append(choice["delta"].get("content", ""))
            if choice["finish_reason"]:
                finish = choice["finish_reason"]
        c.close()
        assert done and finish == "stop"
        assert "".join(deltas) == text

    @pytest.mark.gateway(allow_no_stream=True)
    def test_healthz_and_metrics(self, gw):
        c = Conn(gw.port, "GET", "/healthz")
        h = c.body_json()
        c.close()
        assert c.status == 200 and h["ok"] and not h["draining"]
        c = Conn(gw.port, "GET", "/metrics")
        assert c.status == 200
        text = c.f.read().decode("utf-8")
        c.close()
        assert "roundtable_gateway_admitted_total" in text


# ---------------------------------------------------------------------
# native discussions: event ids, reconnect, gauge hygiene
# ---------------------------------------------------------------------


@pytest.mark.gateway
class TestDiscussions:
    def test_multi_row_event_ids_and_gauge(self, gw):
        """Two knights stream through one id-sequence; the event ids
        carry the cumulative per-row watermark; the per-stream inflight
        gauge dies with the stream (RT-GAUGE-LEAK)."""
        body = {"session": "disc-ids", "max_new_tokens": 6,
                "turns": [{"knight": "lancelot", "prompt": PROMPT},
                          {"knight": "galahad", "prompt": PROMPT2}]}
        meta, toks, terminal = read_stream(gw.port, "/v1/discussions",
                                           body)
        assert meta is not None and meta["knights"] == ["lancelot",
                                                        "galahad"]
        assert terminal is not None and terminal["type"] == "retired"
        per_row = row_tokens(toks, 2)
        assert all(len(r) > 0 for r in per_row)

        # ids: parseable, same turn, and EXACT per event — each id's
        # counts equal precisely the tokens delivered up to and
        # including that event (not the whole batch's post-state), so
        # a client cut off anywhere holds a watermark that skips
        # nothing on reconnect.
        running = [0, 0]
        for eid, ev in toks:
            parsed = parse_event_id(eid, 2)
            assert parsed is not None and parsed[0] == meta["turn"]
            if ev["type"] == "tokens":
                running[ev["row"]] += len(ev["tokens"])
            else:  # summary
                for i, d in ev["rows"].items():
                    running[int(i)] += len(d["tokens"])
            assert parsed[1] == running, (
                f"event id {eid} counts tokens the client has not "
                f"received yet (delivered so far: {running})")
        assert running == [len(r) for r in per_row]

        # the stream retired -> its gauge series must be GONE.
        sid = meta["stream"]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if telemetry.REGISTRY.gauge_value(
                    "roundtable_gateway_inflight_streams",
                    request=sid) is None:
                break
            time.sleep(0.05)
        assert telemetry.REGISTRY.gauge_value(
            "roundtable_gateway_inflight_streams", request=sid) is None

    def test_reconnect_watermark_no_loss_no_dup(self, gw):
        """A client that saw a mid-stream event id reconnects with it
        as Last-Event-ID and receives EXACTLY the rest: prefix + resume
        == the full stream, token for token."""
        body = {"session": "disc-rc", "max_new_tokens": 6,
                "turns": [{"knight": "lancelot", "prompt": PROMPT},
                          {"knight": "galahad", "prompt": PROMPT2}]}
        meta, toks, terminal = read_stream(gw.port, "/v1/discussions",
                                           body)
        assert terminal["type"] == "retired"
        full = row_tokens(toks, 2)
        assert toks, "stream produced no token events"

        # Watermark = after the FIRST token event.
        mid_id = toks[0][0]
        mid = parse_event_id(mid_id, 2)[1]
        prefix = [full[i][:mid[i]] for i in range(2)]

        meta2, toks2, terminal2 = read_stream(
            gw.port, f"/v1/streams/{meta['stream']}", method="GET",
            headers={"Last-Event-ID": mid_id})
        assert meta2["stream"] == meta["stream"]
        assert terminal2["type"] == "retired"
        resumed = row_tokens(toks2, 2)
        assert [p + r for p, r in zip(prefix, resumed)] == full, \
            "reconnect lost or duplicated tokens"
        assert gw.resumed_streams >= 1

    def test_restart_reconnect_serves_committed_turn(self, gw):
        """Reconnect ladder leg 2 in-process: a FRESH Gateway (empty
        stream table, reloaded intent journal — the post-restart state)
        serves a finished stream's tokens straight from the session
        journal's committed record."""
        body = {"session": "disc-restart", "max_new_tokens": 6,
                "turns": [{"knight": "lancelot", "prompt": PROMPT}]}
        meta, toks, terminal = read_stream(gw.port, "/v1/discussions",
                                           body)
        assert terminal["type"] == "retired"
        full = row_tokens(toks, 1)

        gw2 = Gateway(gw.sched, port=0,
                      intent_dir=str(gw.intents.root))
        gw2.start_in_thread()
        try:
            meta2, toks2, terminal2 = read_stream(
                gw2.port, f"/v1/streams/{meta['stream']}",
                method="GET")
            assert terminal2["type"] == "retired"
            assert row_tokens(toks2, 1) == full
            # and with the final watermark: nothing re-sent.
            final_id = format_event_id(meta["turn"],
                                       [len(full[0])])
            _m, toks3, terminal3 = read_stream(
                gw2.port, f"/v1/streams/{meta['stream']}",
                method="GET", headers={"Last-Event-ID": final_id})
            assert toks3 == [] and terminal3["type"] == "retired"
        finally:
            gw2.stop()

    def test_restart_regenerates_uncommitted_turn(self, gw,
                                                  unit_engine,
                                                  tmp_path):
        """Reconnect ladder leg 3 in-process: the stream's intent
        record survived but its turn is NOT in the session journal
        (the crash landed mid-round) — the restore re-submits from the
        recorded prompts and greedy regeneration reproduces the
        IDENTICAL token stream, the client's watermark skipping what
        it already saw."""
        body = {"session": "disc-leg3", "max_new_tokens": 6,
                "turns": [{"knight": "lancelot", "prompt": PROMPT2}]}
        meta, toks, terminal = read_stream(gw.port, "/v1/discussions",
                                           body)
        assert terminal["type"] == "retired"
        full = row_tokens(toks, 1)
        mid_id = toks[0][0]
        mid = parse_event_id(mid_id, 1)[1]

        # A scheduler whose session journal never saw the turn: the
        # committed-record leg is unavailable, so the restore MUST
        # regenerate (a different engine instance, same deterministic
        # weights — exactly the post-restart situation).
        sched2 = SessionScheduler(
            unit_engine, journal=SessionJournal(tmp_path / "empty"))
        gw3 = Gateway(sched2, port=0, intent_dir=str(gw.intents.root))
        gw3.start_in_thread()
        try:
            _m, toks3, term3 = read_stream(
                gw3.port, f"/v1/streams/{meta['stream']}",
                method="GET", headers={"Last-Event-ID": mid_id})
            assert term3 is not None and term3["type"] == "retired"
            resumed = row_tokens(toks3, 1)
            assert full[0][:mid[0]] + resumed[0] == full[0], \
                "leg-3 regeneration lost or duplicated tokens"
        finally:
            gw3.stop()
            sched2.close()

    @pytest.mark.gateway(allow_no_stream=True)
    def test_unknown_stream_404(self, gw):
        c = Conn(gw.port, "GET", "/v1/streams/deadbeef00000000")
        assert c.status == 404
        assert c.body_json()["reason"] == "unknown_stream"
        c.close()

    @pytest.mark.gateway(allow_no_stream=True)
    def test_restart_refuses_sampled_uncommitted(self, unit_engine,
                                                 tmp_path):
        """Reconnect ladder leg 3 only holds for GREEDY streams: an
        intent recorded with temperature > 0 whose turn never committed
        cannot regenerate byte-identically, so the reconnect is refused
        (409 nondeterministic_stream) instead of splicing a different
        token stream onto the client's watermark."""
        from theroundtaible_tpu.gateway.resume import StreamIntentJournal
        jdir = tmp_path / "sampled-intents"
        rec = StreamIntentJournal(jdir).record(
            "samp000000000001", session="s-sampled",
            knights=["lancelot"], prompts=[PROMPT], turn=0, max_new=4,
            temperature=0.8)
        assert rec is not None and rec["temperature"] == 0.8
        sched = SessionScheduler(
            unit_engine, journal=SessionJournal(tmp_path / "empty-j"))
        gws = Gateway(sched, port=0, intent_dir=str(jdir))
        gws.start_in_thread()
        try:
            c = Conn(gws.port, "GET", "/v1/streams/samp000000000001")
            assert c.status == 409
            assert c.body_json()["reason"] == "nondeterministic_stream"
            c.close()
        finally:
            gws.stop()
            sched.close()

    @pytest.mark.gateway(allow_no_stream=True)
    def test_late_pump_failure_no_second_head(self, gw):
        """A pump-path failure AFTER the SSE head went out must never
        write a second HTTP status line onto the same socket — the
        error arrives as a terminal `failed` SSE event mid-stream."""
        gwx = Gateway(gw.sched, port=0)

        def boom(_state, _ev):
            raise RuntimeError("pump exploded")

        gwx._native_payload = boom
        gwx.start_in_thread()
        c = None
        try:
            c = Conn(gwx.port, "POST", "/v1/discussions",
                     body={"session": "late-fail", "max_new_tokens": 2,
                           "turns": [{"knight": "lancelot",
                                      "prompt": PROMPT}]})
            assert c.status == 200  # the one and only response head
            raw = c.f.read()
            assert b"HTTP/1.1" not in raw, \
                "second HTTP head written mid-SSE-stream"
            datas = [json.loads(ln[6:].decode("utf-8"))
                     for ln in raw.split(b"\n")
                     if ln.startswith(b"data: ")]
            assert any(d.get("type") == "failed"
                       and d.get("kind") == "internal"
                       for d in datas)
        finally:
            if c is not None:
                c.close()
            gwx.stop()


# ---------------------------------------------------------------------
# admission: shed ladder, drain, deadline propagation
# ---------------------------------------------------------------------


@pytest.mark.gateway(allow_no_stream=True)
class TestAdmission:
    def test_inflight_cap_sheds_429(self, gw):
        """An at-cap gateway sheds with 429 + Retry-After + a
        machine-readable reason, and the counters move."""
        capped = Gateway(gw.sched, port=0,
                         admission=AdmissionController(
                             gw.sched, max_inflight=1))
        capped.start_in_thread()
        first = None
        try:
            shed0 = telemetry.REGISTRY.counter_total(
                "roundtable_gateway_shed_total", reason="inflight_cap")
            # Fill the one slot with a long stream; its metadata event
            # arriving proves the stream is registered inflight.
            first = Conn(capped.port, "POST", "/v1/discussions",
                         body={"session": "cap-a",
                               "max_new_tokens": 64,
                               "turns": [{"knight": "lancelot",
                                          "prompt": PROMPT}]})
            assert first.status == 200
            meta = json.loads(next(first.events())[1])
            assert meta["type"] == "stream"

            c = Conn(capped.port, "POST", "/v1/chat/completions",
                     body={"messages": [{"role": "user",
                                         "content": "hi"}]})
            assert c.status == 429
            payload = c.body_json()
            c.close()
            assert payload["reason"] == "inflight_cap"
            assert int(c.headers["retry-after"]) >= 1
            assert capped.admission.shed == 1
            assert telemetry.REGISTRY.counter_total(
                "roundtable_gateway_shed_total",
                reason="inflight_cap") == shed0 + 1
            assert capped.describe()["shed"] == 1
        finally:
            if first is not None:
                first.close()
            capped.stop()

    def test_drain_sheds_503(self, gw):
        """fleet drain / paused admission → 503 draining; a custom
        pause reason is machine-distinguishable."""
        gw.sched.pause_admission("fleet.drain")
        try:
            c = Conn(gw.port, "POST", "/v1/discussions",
                     body={"turns": [{"knight": "k", "prompt": "x"}]})
            assert c.status == 503
            assert c.body_json()["reason"] == "draining"
            assert "retry-after" in c.headers
            c.close()
            h = Conn(gw.port, "GET", "/healthz")
            assert h.body_json()["draining"] is True
            h.close()
        finally:
            gw.sched.reopen_admission()

        gw.sched.pause_admission("maintenance")
        try:
            c = Conn(gw.port, "POST", "/v1/discussions",
                     body={"turns": [{"knight": "k", "prompt": "x"}]})
            assert c.status == 503
            assert c.body_json()["reason"] == "paused:maintenance"
            c.close()
        finally:
            gw.sched.reopen_admission()

    def test_deadline_expired_sheds_408(self, gw):
        """A spent client deadline never reaches the scheduler: 408
        with the deadline_expired reason, expired counter moves."""
        e0 = telemetry.REGISTRY.counter_total(
            "roundtable_gateway_expired_total",
            reason="deadline_expired")
        c = Conn(gw.port, "POST", "/v1/chat/completions",
                 body={"messages": [{"role": "user", "content": "hi"}]},
                 headers={"X-Roundtable-Deadline-S": "0"})
        assert c.status == 408
        assert c.body_json()["reason"] == "deadline_expired"
        c.close()
        assert telemetry.REGISTRY.counter_total(
            "roundtable_gateway_expired_total",
            reason="deadline_expired") == e0 + 1

    def test_queued_counter_counts_queue_path(self, unit_engine):
        """An admission that parks behind a NONEMPTY scheduler queue
        is the queue path: Decision.queued rides into note_admitted and
        moves roundtable_gateway_queued_total in lockstep."""

        class _StubSched:
            paused = None

            def __init__(self, engine, depth):
                self.engine = engine
                self._depth = depth

            def describe(self):
                return {"admission": {"queued": self._depth}}

        q0 = telemetry.REGISTRY.counter_total(
            "roundtable_gateway_queued_total", reason="behind_queue")
        adm = AdmissionController(_StubSched(unit_engine, 3),
                                  max_inflight=8, max_queue_depth=16)
        d = adm.decide(rows=1, inflight=1)
        assert d.admit and d.queued
        adm.note_admitted(queued=d.queued)
        assert adm.admitted == 1 and adm.queued == 1
        assert adm.describe()["queued"] == 1
        assert telemetry.REGISTRY.counter_total(
            "roundtable_gateway_queued_total",
            reason="behind_queue") == q0 + 1

        # Empty scheduler queue: admitted immediately, NOT queued.
        adm2 = AdmissionController(_StubSched(unit_engine, 0),
                                   max_inflight=8, max_queue_depth=16)
        d2 = adm2.decide(rows=1, inflight=1)
        assert d2.admit and not d2.queued
        adm2.note_admitted(queued=d2.queued)
        assert adm2.queued == 0

    def test_priority_scales_caps(self, gw):
        """Low-priority traffic sheds at half the configured caps;
        high priority bypasses the soft p95 signal."""
        adm = AdmissionController(gw.sched, max_inflight=4,
                                  p95_slo_s=0.001)
        # low: cap halves to 2 → inflight 2 sheds.
        d = adm.decide(rows=1, inflight=2, priority="low")
        assert not d.admit and d.reason == "inflight_cap"
        assert adm.decide(rows=1, inflight=2,
                          priority="normal").admit
        # soft p95 over SLO sheds normal but not high priority.
        for _ in range(16):
            adm.note_ttft(1.0)
        d = adm.decide(rows=1, inflight=0, priority="normal")
        assert not d.admit and d.reason == "slo_p95" and d.status == 429
        assert adm.decide(rows=1, inflight=0, priority="high").admit


# ---------------------------------------------------------------------
# scheduler-level: deadline fast-fail, pause-reason threading
# ---------------------------------------------------------------------


@pytest.mark.gateway(allow_no_stream=True)
class TestSchedulerSeam:
    def test_spent_budget_fails_fast_no_prefill(self, unit_engine):
        """submit_async with an already-expired Budget raises
        DeadlineExpired (its OWN classified kind) before any prefill
        dispatch — zero segment tokens consumed, nothing queued."""
        sched = SessionScheduler(unit_engine)
        try:
            d0 = sched.describe()
            assert d0["deadline_expired"] == 0
            with pytest.raises(DeadlineExpired) as ei:
                sched.submit_async(
                    "dead", [("lancelot", PROMPT)], max_new_tokens=4,
                    budget=deadlines.Budget.root(0.0, rung="turn"))
            assert classify_error(ei.value) == "deadline_expired"
            d = sched.describe()
            assert d["deadline_expired"] == 1
            assert d["segment_prefill_tokens"] == \
                d0["segment_prefill_tokens"], "prefill was consumed"
            assert d["admission"]["queued"] == 0
            assert d["active_rows"] == 0
            assert telemetry.REGISTRY.counter_total(
                "roundtable_sched_deadline_expired_total") >= 1
        finally:
            sched.close()

    def test_pause_reasons_thread_into_refusal(self, unit_engine):
        """Every pause reason rides verbatim on SchedulerRefused.reason
        for shed-style submitters and shows in describe()["admission"]:
        drain, quiesce, and a caller-defined gate."""
        sched = SessionScheduler(unit_engine)
        try:
            for reason in ("fleet.drain", "quiesce", "gateway.shed"):
                sched.pause_admission(reason)
                adm = sched.describe()["admission"]
                assert adm["paused"] == reason and not adm["open"]
                with pytest.raises(SchedulerRefused) as ei:
                    sched.submit_async("pz", [("k", "hi")],
                                       max_new_tokens=2,
                                       queue_when_paused=False)
                assert ei.value.reason == reason
                sched.reopen_admission()
                assert sched.describe()["admission"]["open"]
            # bare refusals still carry no reason tag.
            assert SchedulerRefused("plain").reason is None
        finally:
            sched.close()


# ---------------------------------------------------------------------
# resume seam + surface bindings + status view
# ---------------------------------------------------------------------


@pytest.mark.gateway(allow_no_stream=True)
class TestSeams:
    def test_resume_library_seam_identity(self):
        """The CLI path re-exports the library function — one resume
        implementation, byte-identical behavior (the supervision suite
        regression-tests it through the commands.serve import)."""
        from theroundtaible_tpu.commands.serve import \
            resume_from_journal as cli_fn
        from theroundtaible_tpu.engine.recovery import \
            resume_from_journal as lib_fn
        assert cli_fn is lib_fn

    def test_replay_through_library_seam(self, unit_engine, tmp_path):
        """A journaled round replays through engine.recovery directly
        onto a fresh scheduler (the gateway's boot path)."""
        from theroundtaible_tpu.engine.recovery import resume_from_journal

        j = SessionJournal(tmp_path)
        sched = SessionScheduler(unit_engine, journal=j)
        try:
            sched.submit("lib-replay", [("lancelot", PROMPT)],
                         max_new_tokens=4, timeout_s=120)
        finally:
            sched.close()
        sched2 = SessionScheduler(unit_engine)
        try:
            report = resume_from_journal(str(tmp_path),
                                         scheduler=sched2)
            assert report["sessions"] == 1
            assert report["turns"] == 1
            assert report["scheduler"] is sched2
            assert sched2.journal is not None
        finally:
            sched2.close()

    def test_describe_keys_bound_to_surface(self, gw):
        from theroundtaible_tpu.utils.telemetry import SURFACE_BINDINGS
        assert set(gw.describe()) <= set(SURFACE_BINDINGS["gateway"])

    def test_status_gateway_renders(self, gw, capsys):
        from theroundtaible_tpu.commands.status import status_command
        # Seed one series so the render has a reason table even when
        # this test runs alone (counters are global and additive).
        telemetry.inc("roundtable_gateway_admitted_total", reason="ok")
        assert status_command(gateway_view=True) == 0
        out = capsys.readouterr().out
        assert "Serving gateway" in out
        assert "Admitted" in out

    def test_intent_record_roundtrips_adapters_temperature(
            self, tmp_path):
        """The intent record persists the full generation identity —
        adapters + temperature included — so leg-3 resume replays the
        SAME stream, not a base-model/greedy approximation of it."""
        from theroundtaible_tpu.gateway.resume import StreamIntentJournal
        j = StreamIntentJournal(tmp_path)
        rec = j.record("r1", session="s", knights=["k"],
                       prompts=["p"], turn=2, max_new=4,
                       adapters=["persona-a"], temperature=0.5)
        loaded = j.load()["r1"]
        assert loaded == rec
        assert loaded["adapters"] == ["persona-a"]
        assert loaded["temperature"] == 0.5

    def test_intent_journal_compacts(self, unit_engine, tmp_path):
        """A long-lived gateway bounds the intent journal + cache:
        past the cap, records whose turn committed in the session
        journal compact away (newest half of the cap kept for leg-2
        reconnects); uncommitted intents — a crash needs them for
        leg-3 regeneration — always survive."""
        j = SessionJournal(tmp_path)
        sched = SessionScheduler(unit_engine, journal=j)
        try:
            sched.submit("compact-s", [("lancelot", PROMPT)],
                         max_new_tokens=2, timeout_s=120)
            gwc = Gateway(sched, port=0, intent_dir=str(tmp_path))
            for i in range(12):  # committed (turn 0 is journaled)
                sid = f"done{i:04d}"
                gwc._intent_cache[sid] = gwc.intents.record(
                    sid, session="compact-s", knights=["lancelot"],
                    prompts=[PROMPT], turn=0, max_new=2)
            # uncommitted (turn 9 never ran)
            gwc._intent_cache["live0001"] = gwc.intents.record(
                "live0001", session="compact-s", knights=["lancelot"],
                prompts=[PROMPT], turn=9, max_new=2)
            gwc.intent_cap = 8
            gwc._compact_intents()
            assert "live0001" in gwc._intent_cache
            kept = [s for s in gwc._intent_cache
                    if s.startswith("done")]
            assert kept == [f"done{i:04d}" for i in range(8, 12)]
            # disk and cache agree about who can still reconnect.
            assert set(gwc.intents.load()) == set(gwc._intent_cache)
            # below the cap again: a second pass is a no-op.
            n = len(gwc._intent_cache)
            gwc._compact_intents()
            assert len(gwc._intent_cache) == n
        finally:
            sched.close()

    def test_event_id_roundtrip(self):
        assert parse_event_id(format_event_id(3, [5, 7]), 2) \
            == (3, [5, 7])
        assert parse_event_id("3:5,7", 3) is None   # row mismatch
        assert parse_event_id("junk", 2) is None
        assert parse_event_id("-1:0,0", 2) is None


# ---------------------------------------------------------------------
# THE chaos acceptance: kill -9 under concurrent streams
# ---------------------------------------------------------------------


def _spawn_gateway(jdir, resume=None):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable,
           os.path.join(repo, "tests", "_gateway_main.py"),
           "--journal", str(jdir)]
    if resume:
        cmd += ["--resume", str(resume)]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ROUNDTABLE_RECOMPILE_STRICT="1")
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    port = None
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
    assert port is not None, "gateway child never started listening"

    def _drain(stream):  # keep the child's pipe from filling up
        for _line in stream:
            pass

    import threading
    threading.Thread(target=_drain, args=(proc.stdout,),
                     daemon=True).start()
    return proc, port


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.gateway(allow_no_stream=True)  # the CHILD streams the
# tokens over its socket; this process only reads them.
def test_kill9_streams_resume_with_token_parity(tmp_path):
    """THE crash acceptance: kill -9 the gateway mid-stream under 3
    concurrent sessions, restart it with --resume, and reconnect every
    client via Last-Event-ID — zero lost, zero duplicated tokens, and
    greedy parity with an uninterrupted reference run."""
    jdir = tmp_path / "journal"
    sessions = [("c0", PROMPT), ("c1", PROMPT2),
                ("c2", PROMPT + " Galahad raises the matter of the "
                                "moat.")]
    # Two 64-token decode segments: the first commit streams 64 tokens,
    # then the SIGKILL lands while the turn is still UNCOMMITTED — the
    # resume must regenerate (leg 3), not just replay a journaled turn.
    max_new = 96

    proc, port = _spawn_gateway(jdir)
    conns, metas, seen = [], [], []
    try:
        # Reference run FIRST (same child process = same weights):
        # uninterrupted streams on shadow sessions with the same
        # prompts — greedy, so the crashed sessions must match.
        refs = []
        for name, prompt in sessions:
            _m, toks, term = read_stream(
                port, "/v1/discussions",
                {"session": f"ref-{name}", "max_new_tokens": max_new,
                 "turns": [{"knight": "lancelot", "prompt": prompt}]})
            assert term["type"] == "retired"
            refs.append(row_tokens(toks, 1)[0])
            assert refs[-1], "reference stream produced nothing"

        # Open 3 live streams and read only PART of each (the crash
        # happens mid-stream from the clients' point of view).
        for name, prompt in sessions:
            c = Conn(port, "POST", "/v1/discussions",
                     body={"session": name, "max_new_tokens": max_new,
                           "turns": [{"knight": "lancelot",
                                      "prompt": prompt}]})
            assert c.status == 200
            conns.append(c)
        for c in conns:
            it = c.events()
            meta = json.loads(next(it)[1])
            assert meta["type"] == "stream"
            metas.append(meta)
            got, last_id = [], None
            for eid, data in it:
                ev = json.loads(data)
                if ev["type"] in ("tokens", "summary"):
                    got.extend(row_tokens([(eid, ev)], 1)[0])
                    last_id = eid
                if len(got) >= 2:
                    break
            assert last_id is not None, "no tokens before the crash"
            seen.append((got, last_id))
    finally:
        proc.kill()  # SIGKILL — no atexit, no flush, no goodbye
        proc.wait(30)
        for c in conns:
            c.close()

    # Restart with --resume: committed turns replay into KV, the
    # intent journal restores the crashed streams (leg 3: greedy
    # re-generation), and every client resumes at its watermark.
    proc2, port2 = _spawn_gateway(jdir, resume=jdir)
    try:
        for (name, _p), meta, (got, last_id), ref in zip(
                sessions, metas, seen, refs):
            _m2, toks2, term2 = read_stream(
                port2, f"/v1/streams/{meta['stream']}", method="GET",
                headers={"Last-Event-ID": last_id})
            assert term2 is not None and term2["type"] == "retired", \
                f"{name}: resumed stream did not retire cleanly"
            resumed = row_tokens(toks2, 1)[0]
            assert got + resumed == ref, (
                f"{name}: prefix({len(got)}) + resumed({len(resumed)}) "
                f"!= uninterrupted reference ({len(ref)}) — tokens "
                "lost or duplicated across the crash")
    finally:
        proc2.kill()
        proc2.wait(30)
