#!/bin/bash
# Round-5 hardware window #3 — after window #2 measured the int8
# headline (205 tok/s), config 2's first-ever discuss wall-clock
# (19.91 s = 1.256x), and found end-to-end int4 still materializing
# (31.6 tok/s), the fused Pallas w4a16 kernels (pallas/int4mm.py)
# landed. This window:
#   0. parks on a probe loop until the tunnel revives (probe_tunnel
#      abandons hung children — never SIGKILL a JAX process, a killed
#      child is the suspected relay-wedge event)
#   1. bench_microquant.py  — do the kernels Mosaic-compile and stream
#                             packed bytes? (int4-kernel / head-int4-
#                             kernel variants; dependency-chained timing)
#   2. bench.py             — all 4 configs; int4 decode now takes the
#                             kernel path end to end
#   3. bench_suite.py all   — configs 3-5, never measured this round
#   4. bench_profile.py     — attribution for whatever still lags
#   5. realweights on-chip  — stretch, LAST so a hang costs no data
# Same per-step artifact-commit discipline as windows 1-2 (shared lib).
set -u
cd "$(dirname "$0")" || exit 1
OUT=BENCH_r05_builder.jsonl
. ./hw_window_lib.sh

# Preflight (ISSUE 15): the static serving-invariant analyzer runs
# BEFORE the probe loop, on CPU, with zero devices — a statically
# detectable violation (gauge leak, static-arg recompile, callback in
# the hot loop, donation misuse) must never cost a tunnel window. The
# --jaxpr audit traces every registered serving program; --json output
# lands next to the bench artifacts for the record.
if ! env JAX_PLATFORMS=cpu python -m theroundtaible_tpu lint --jaxpr \
    --json > LINT_preflight.json 2>> "$OUT.log"; then
  echo "window3: roundtable lint FAILED $(stamp) — fix the findings" \
       "in LINT_preflight.json before spending a window" >> "$OUT.log"
  exit 1
fi
echo "window3: lint preflight clean $(stamp)" >> "$OUT.log"

# Gateway preflight (ISSUE 16): the serving front door must survive a
# kill -9 + --resume round-trip and shed overload well-formed on CPU
# before any window time is spent — a gateway that can't restart
# cleanly would strand every client mid-stream on the real chips.
if ! env JAX_PLATFORMS=cpu python bench_gateway.py --smoke \
    >> "$OUT.log" 2>&1; then
  echo "window3: gateway smoke FAILED $(stamp) — fix the serving" \
       "front door before spending a window" >> "$OUT.log"
  exit 1
fi
echo "window3: gateway smoke clean $(stamp)" >> "$OUT.log"

# Router preflight (ISSUE 17): a rolling restart of one replica in a
# 2-replica CPU fleet under a live client — drain, migrate, rebuild,
# re-admit with zero failed sessions and greedy parity — must pass
# before any window time is spent; a fleet that cannot roll would
# turn every planned restart on the real chips into an outage.
if ! env JAX_PLATFORMS=cpu python bench_gateway.py --smoke \
    --replicas 2 >> "$OUT.log" 2>&1; then
  echo "window3: router smoke FAILED $(stamp) — fix the replica" \
       "fleet before spending a window" >> "$OUT.log"
  exit 1
fi
echo "window3: router smoke clean $(stamp)" >> "$OUT.log"

# Loadgen preflight (ISSUE 19): a tiny open-loop Poisson sweep on CPU
# (~30 s) must reach a shed point and fit a knee before any window
# time is spent — a harness that cannot find the capacity frontier on
# CPU would waste the chips measuring nothing; the on-chip sweep later
# reuses this exact path with real rates.
if ! env JAX_PLATFORMS=cpu python bench_load.py --smoke \
    >> "$OUT.log" 2>&1; then
  echo "window3: loadgen smoke FAILED $(stamp) — fix the offered-load" \
       "harness before spending a window" >> "$OUT.log"
  exit 1
fi
echo "window3: loadgen smoke clean $(stamp)" >> "$OUT.log"

# Tracing preflight (ISSUE 20): one client request must stitch to ONE
# on-disk trace across a cross-replica failover and a kill -9 +
# --resume restart, with per-leg stage sums telescoping to the leg
# wall and the SLO burn monitor firing only on an induced breach —
# broken trace propagation would leave the on-chip windows with
# unattributable TTFT tails.
if ! env JAX_PLATFORMS=cpu python bench_gateway.py --trace --smoke \
    >> "$OUT.log" 2>&1; then
  echo "window3: tracing smoke FAILED $(stamp) — fix trace" \
       "propagation before spending a window" >> "$OUT.log"
  exit 1
fi
echo "window3: tracing smoke clean $(stamp)" >> "$OUT.log"

while :; do
  python - <<'PY' 2>> "$OUT.log"
import sys
try:
    from bench_common import probe_tunnel
    ok = probe_tunnel()
except Exception:
    import traceback
    traceback.print_exc()
    sys.exit(2)          # probe CRASHED — not a dead tunnel
sys.exit(0 if ok else 1)
PY
  rc=$?
  [ "$rc" -eq 0 ] && break
  if [ "$rc" -ge 2 ]; then
    # a crashing probe must abort loudly, not impersonate a dead
    # tunnel forever (traceback is in $OUT.log just above)
    echo "window3: probe CRASHED rc=$rc $(stamp) — aborting" >> "$OUT.log"
    exit 1
  fi
  echo "window3: tunnel dead $(stamp), re-probe in 300s" >> "$OUT.log"
  sleep 300
done
echo "window3: tunnel alive $(stamp)" >> "$OUT.log"

run_step "bench_microquant.py (fused kernels)" python bench_microquant.py
run_step "bench.py (config 1, int4 kernel path)" python bench.py
run_step "bench_suite.py (configs 3-5)" python bench_suite.py all
run_step "bench_profile.py" python bench_profile.py
# Speculative decoding A/B (ISSUE 9): scripted multi-round discussion
# spec-on vs spec-off on chip — acceptance by round, accepted tok/s,
# greedy parity bit. Every perf claim needs its window-3 baseline.
run_step "bench_discuss.py (spec-decode A/B)" \
  env ROUNDTABLE_BENCH_SPEC_DECODE=1 python bench_discuss.py
# Multi-LoRA persona A/B (ISSUE 10): the K-knight load as K LoRA
# personas co-batched on ONE shared base vs a K-checkpoint fleet —
# aggregate tok/s, resident HBM per mode (the < 1.5x-single-base bar),
# persona distribution divergence, mixed-vs-alone parity bit.
run_step "bench_discuss.py (multi-LoRA A/B)" \
  env ROUNDTABLE_BENCH_LORA=1 python bench_discuss.py
# Quantized-KV-page A/B (ISSUE 11): the same pool byte budget served
# int8-KV-on vs bf16-off on chip (gemma-2b D=256 → page ratio 1.97x) —
# max resident sessions before eviction (the >= 1.8x bar), scheduled
# decode tok/s, ledger resident/logical split, greedy parity bit,
# per-page-path dequant provenance, STRICT green. The CPU twin of
# this record is KVQ_r11.json.
run_step "bench_discuss.py (KV-quant A/B)" \
  env ROUNDTABLE_BENCH_KV_QUANT=1 python bench_discuss.py
# Draft-model + tree speculation A/B (ISSUE 13): SAMPLED realweights
# traffic through the scheduler — ngram chain vs draft-model chain vs
# model/LoRA tree verify. On-chip the headline is accepted tokens per
# verify dispatch on sampled traffic (the CPU twin is TREE_r13.json;
# scripted acceptance 1.0 is disallowed as evidence, BENCH_NOTES.md)
# plus greedy parity and the kill-switch zero-dispatch bit. Needs the
# cached checkpoint, so it runs after the probe loop and before the
# long realweights serve.
run_step "bench_realweights.py --spec (tree-spec A/B)" \
  timeout 900 python bench_realweights.py --spec --budget-s 840
git add TREE_r13.json 2>/dev/null && \
  git commit -q -o TREE_r13.json \
    -m "Hardware window 3: on-chip tree-speculation A/B artifact

No-Verification-Needed: measurement artifact only, no source change" \
  || true
# 1500 s: the 900 s budget SIGTERMed twice — host-side training alone
# is ~330 s and first-time tunnel compiles are 20-40 s per prefill
# shape bucket. Still LAST so even a hang costs no core measurement.
run_step "bench_realweights.py (on-chip)" \
  timeout 1500 python bench_realweights.py --min-turns 20 --budget-s 1440
git add REALWEIGHTS_r05.json 2>/dev/null && \
  git commit -q -o REALWEIGHTS_r05.json \
    -m "Hardware window 3: on-chip realweights artifact

No-Verification-Needed: measurement artifact only, no source change" \
  || true
echo "window 3 complete: $(stamp)"; tail -n +1 "$OUT" | wc -l
