"""Benchmark suite — BASELINE.md measured configs 3, 4 and 5.

Prints ONE JSON line per requested config (bench.py covers config 1,
bench_discuss.py covers config 2):

  python bench_suite.py fleet    # 3: heterogeneous 3-model round
  python bench_suite.py summon   # 4: long-context prefill (2k-line diff)
  python bench_suite.py apply    # 5: lead-knight long decode
  python bench_suite.py all      # one JSON line each

On the real chip the models are the flagship sizes; under
ROUNDTABLE_BENCH_CPU=1 the tiny trio keeps it a smoke test. Same
child-process watchdog as bench.py (the single-claim TPU tunnel hangs
rather than erroring while held).

The reference publishes no numbers for any of these (BASELINE.md
"published: {}"); vs_baseline anchors:
- fleet: 3 serial Ollama turns at ~120 tok/s decode, 160 tok each ≈ 4 s
  of decode per round — our 3 submeshes run the round concurrently.
- summon: llama.cpp prefill on A100 ≈ 3000 tok/s for 7B-class models.
- apply: the same 120 tok/s decode anchor as config 1.
"""

from __future__ import annotations

import json
import os
import sys
import time

ATTEMPT_TIMEOUT_S = 420.0
MAX_ATTEMPTS = 2
RETRY_DELAY_S = 20.0

FLEET_ROUND_ANCHOR_S = 4.0
SUMMON_PREFILL_ANCHOR_TPS = 3000.0
APPLY_DECODE_ANCHOR_TPS = 120.0


def _setup():
    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    from theroundtaible_tpu.engine import enable_compilation_cache
    enable_compilation_cache()
    on_cpu = jax.devices()[0].platform == "cpu"
    return jax, on_cpu


def bench_fleet() -> dict:
    """Config 3: three different models resident at once, one round
    dispatched concurrently to all three submeshes."""
    jax, on_cpu = _setup()
    from concurrent.futures import ThreadPoolExecutor

    from theroundtaible_tpu.engine import get_engine, reset_engines
    from theroundtaible_tpu.engine.fleet import plan_fleet

    # Real-chip trio sized to FIT one v5e-1: three distinct models, all
    # int8, ~8.2 GiB estimated resident (fleet.estimate_engine_hbm_bytes)
    # vs the ~12 GiB plannable budget — plan_fleet's HBM check validates
    # this at plan time instead of OOMing mid-serve (VERDICT r2 weak #3;
    # a mistral-7b + gemma-2b + llama-1b trio at ~13 GiB estimated did
    # OOM at concurrent prefill, which set _HBM_UTILIZATION). The full
    # 3-family 7B-class trio is the v5e-8 configuration, where each
    # model gets a disjoint submesh. On one chip the submeshes share
    # device 0 (time-multiplexed residency); largest builds first while
    # the chip is emptiest (quantization peaks above resident size).
    models = (["tiny-gemma", "tiny-llama", "tiny-mistral"] if on_cpu
              else ["llama-3.2-3b-instruct", "gemma-2b-it",
                    "llama-3.2-1b-instruct"])
    max_new = 32 if on_cpu else 160
    configs = [{"model": m, "max_seq_len": 512 if on_cpu else 2048,
                "num_slots": 2,
                **({} if on_cpu else {"quant": "int8"}),
                "sampling": {"temperature": 0.0,
                             "max_new_tokens": max_new}}
               for m in models]
    reset_engines()
    plan_fleet(configs, n_devices=len(jax.devices()))
    engines = [get_engine(c) for c in configs]
    prompt = ("You are a knight at the roundtable. Topic: should the "
              "session store become an event log? Answer briefly. " * 4)

    def turn(engine_i):
        i, engine = engine_i
        return engine.generate(prompt, slot_name=f"knight-{i}",
                               max_new_tokens=max_new)

    # Warm each engine TWICE (bench.py's discipline): the first pass
    # compiles, but its donated KV buffers come back in XLA's preferred
    # layout so the next dispatch would recompile; the second pass
    # reaches the layout fixpoint. One warm pass here measured 26s for a
    # 2s round — all recompiles.
    from bench_common import timed_repeats
    with ThreadPoolExecutor(max_workers=3) as pool:
        for _ in range(2):
            for i, e in enumerate(engines):
                e.kv.release(f"knight-{i}")
            list(pool.map(turn, enumerate(engines)))

        def run_once() -> dict:
            for i, e in enumerate(engines):
                e.kv.release(f"knight-{i}")
            t0 = time.monotonic()
            outs = list(pool.map(turn, enumerate(engines)))
            assert len(outs) == 3
            return {"wall_s": time.monotonic() - t0}

        med, spread, repeats = timed_repeats(run_once)
    wall = med["wall_s"]
    decode_tokens = sum(e.last_stats.decode_tokens for e in engines)
    return {
        "metric": "fleet_round_wall_clock_3models",
        "value": round(wall, 3),
        "unit": "seconds",
        "vs_baseline": round(FLEET_ROUND_ANCHOR_S / max(wall, 1e-9), 3),
        "detail": {
            "models": models,
            "submeshes": [c.get("devices") for c in configs],
            "decode_tokens": decode_tokens,
            "repeats": repeats,
            "spread": {"wall_s": [round(spread["wall_s"][0], 3),
                                  round(spread["wall_s"][1], 3)]},
            "platform": jax.devices()[0].platform,
        },
    }


def bench_summon() -> dict:
    """Config 4: long-context prefill on a git diff sized to FILL the
    engine's context budget (the reference truncates any diff to 3000
    chars, orchestrator.ts:406; we serve the whole window)."""
    jax, on_cpu = _setup()
    from theroundtaible_tpu.engine import get_engine, reset_engines

    reset_engines()
    cfg = {"model": "tiny-gemma" if on_cpu else "gemma-2b-it",
           "max_seq_len": 4096 if on_cpu else 8192, "num_slots": 2,
           "sampling": {"temperature": 0.0, "max_new_tokens": 32}}
    engine = get_engine(cfg)
    # Build the diff to the REAL prompt budget (max_seq minus the padded
    # decode reserve) so nothing is silently head-truncated and the
    # reported tokens are the tokens actually served.
    budget_tokens = engine.max_seq_len - 64 - 1
    budget_chars = int(budget_tokens * engine.chars_per_token() * 0.95)
    lines, total = [], 0
    i = 0
    while total < budget_chars:
        line = f"+    line_{i} = compute_{i % 7}(state, {i})  # changed"
        lines.append(line)
        total += len(line) + 1
        i += 1
    prompt = ("Review this diff:\n" + "\n".join(lines))[:budget_chars]
    # Warm on the FULL prompt (compiles the exact buckets the measured
    # run hits — bench.py's minimal-warmup discipline), then measure on
    # a fresh slot.
    from bench_common import timed_repeats
    for _ in range(2):
        engine.kv.release("warm")
        engine.generate(prompt, slot_name="warm", max_new_tokens=8)

    # Without this release the resident warm slot donates its prefix
    # (share_prefixes) and the "measured" prefill is one token.
    engine.kv.release("warm")

    def run_once() -> dict:
        engine.kv.release("summon")
        t0 = time.monotonic()
        engine.generate(prompt, slot_name="summon", max_new_tokens=32)
        return {"prefill_tps": engine.last_stats.prefill_tps,
                "wall_s": time.monotonic() - t0}

    med, spread, repeats = timed_repeats(run_once)
    s = engine.last_stats
    prefill_tps = med["prefill_tps"]
    return {
        "metric": "summon_long_prefill_tokens_per_sec",
        "value": round(prefill_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(prefill_tps / SUMMON_PREFILL_ANCHOR_TPS, 3),
        "detail": {
            "prefill_tokens": s.prefill_tokens,
            "diff_lines": len(lines),
            "wall_s": round(med["wall_s"], 2),
            "repeats": repeats,
            "spread": {"prefill_tps": [round(spread["prefill_tps"][0], 1),
                                       round(spread["prefill_tps"][1], 1)]},
            "platform": jax.devices()[0].platform,
        },
    }


def bench_apply() -> dict:
    """Config 5: lead-knight long decode (code generation)."""
    jax, on_cpu = _setup()
    from theroundtaible_tpu.engine import get_engine, reset_engines

    max_new = 128 if on_cpu else 1024
    reset_engines()
    cfg = {"model": "tiny-gemma" if on_cpu else "gemma-2b-it",
           "max_seq_len": 1024 if on_cpu else 4096, "num_slots": 2,
           "quant": "none" if on_cpu else "int8",
           "sampling": {"temperature": 0.0, "max_new_tokens": max_new}}
    engine = get_engine(cfg)
    prompt = ("Consensus decision: rewrite the session store as an "
              "append-only event log. Emit the full RTDIFF/1 patch for "
              "every file in scope. " * 4)
    from bench_common import timed_repeats
    for _ in range(2):
        engine.kv.release("warm")
        engine.generate(prompt, slot_name="warm", max_new_tokens=max_new)

    def run_once() -> dict:
        engine.kv.release("apply")
        t0 = time.monotonic()
        engine.generate(prompt, slot_name="apply", max_new_tokens=max_new)
        return {"decode_tps": engine.last_stats.decode_tps,
                "wall_s": time.monotonic() - t0}

    med, spread, repeats = timed_repeats(run_once)
    s = engine.last_stats
    decode_tps = med["decode_tps"]
    return {
        "metric": "apply_long_decode_tokens_per_sec",
        "value": round(decode_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(decode_tps / APPLY_DECODE_ANCHOR_TPS, 3),
        "detail": {
            "decode_tokens": s.decode_tokens,
            "wall_s": round(med["wall_s"], 2),
            "repeats": repeats,
            "spread": {"decode_tps": [round(spread["decode_tps"][0], 2),
                                      round(spread["decode_tps"][1], 2)]},
            "quant": cfg["quant"],
            "platform": jax.devices()[0].platform,
        },
    }


BENCHES = {"fleet": bench_fleet, "summon": bench_summon,
           "apply": bench_apply}


def child(which: str) -> int:
    # NOT install_sigterm_exit: the fleet bench runs engine.generate on
    # ThreadPoolExecutor workers, and a SystemExit in the main thread
    # would block interpreter shutdown on joining workers stuck in JAX
    # C++ until the watchdog's grace expires into SIGKILL. Flush what
    # we have and exit promptly instead — process death closes the
    # relay socket, which is the claim-release path that matters.
    import signal

    def _term(*_):
        sys.stdout.flush()
        os._exit(1)

    signal.signal(signal.SIGTERM, _term)
    for name in (list(BENCHES) if which == "all" else [which]):
        # flush=True: the watchdog salvages a timeout-killed child's
        # stdout, which only works if the line left this buffer.
        print(json.dumps(BENCHES[name]()), flush=True)
    return 0


def main(which: str) -> int:
    """One watchdogged child PER bench (a single `all` child would stack
    5+ engine builds — two of them 7B-class — into one timeout window)."""
    from bench_common import run_watchdogged

    names = list(BENCHES) if which == "all" else [which]
    worst = 0
    for name in names:
        worst = max(worst, run_watchdogged(
            os.path.abspath(__file__), [name], ATTEMPT_TIMEOUT_S,
            MAX_ATTEMPTS, RETRY_DELAY_S))
    return worst


if __name__ == "__main__":
    which = next((a for a in sys.argv[1:] if not a.startswith("-")), "all")
    if which not in list(BENCHES) + ["all"]:
        print(f"usage: bench_suite.py [{'|'.join(BENCHES)}|all]",
              file=sys.stderr)
        sys.exit(2)
    sys.exit(child(which) if "--child" in sys.argv else main(which))
