"""Micro-benchmark: which weight representation actually streams its
bytes on this chip's matmul operand path?

One gemma-2b-shaped GEMV per representation (decode is a chain of
exactly these), timed standalone so a bad int4 layout is attributable
BEFORE burning a full bench window on it. BENCH_r05 measured full int4
decode at 22.9 tok/s vs bf16's 130 — the old interleaved stack+reshape
unpack broke XLA's operand fusion and materialized (+copied) the bf16
weight every token; the profiler showed per-token `copy` /
`shift-right-arithmetic_bitcast_fusion` ops. The fix (engine/quant.py):
pack along the LAST axis and unpack with lax.bitcast_convert_type,
whose nibble pair expands minor-most — no shuffle, fusable. This script
verifies that claim in ~a minute and prints one JSON line per variant:
effective GB/s = streamed_bytes / iter_time vs the ~819 GB/s v5e HBM
roofline.

Variants:
  bf16      plain einsum                           (2 B/param)
  int8      q int8 + per-output-channel scale      (1 B/param)
  int4      Int4Leaf bitcast dequant (shipping)    (0.5 B/param + s4)
  int4-s4   native jnp.int4 storage, convert+scale (0.5 B/param + s4)
            — candidate future layout; also exercises the S4-at-jit-
            boundary path that RecursionError'd under the axon plugin
            when relayout was needed (watchdogged: a crash here is a
            finding, not a wedge).
  int4-kernel / head-int4-kernel
            the fused Pallas w4a16 kernels (pallas/int4mm.py) that
            dequantize in VMEM — the path engine serving now takes on
            single-device TPU. These are the numbers that decide
            whether int4 decode finally streams packed bytes.

Usage: python bench_microquant.py          (needs the live chip)
       ROUNDTABLE_BENCH_CPU=1 ...          (CPU smoke — numbers are
                                            meaningless, plumbing runs)
Same watchdogged child-process pattern as every sibling bench: the
parent probes first and ABANDONS a hung child (no SIGKILL — a killed
JAX process can wedge the single-claim relay for the whole window).
"""

from __future__ import annotations

import json
import os
import sys
import time

E, F = 2048, 16384          # gemma-2b MLP up-projection shape
GROUP = 64
ITERS = 50
ATTEMPT_TIMEOUT_S = 300.0

# The HBM roofline each variant's effective GB/s is judged against
# comes from the ONE shared model (ISSUE 6) — the v5e 819 GB/s figure
# this docstring cites used to be a local literal.
from theroundtaible_tpu.utils import perfmodel as _perfmodel

_DEFAULT_HBM_GBPS = _perfmodel.V5E_HBM_GBPS


def _hbm_roofline_gbps(device_kind: str) -> float:
    """Detected chip's HBM bandwidth, defaulting to v5e (the CPU smoke
    path — numbers are meaningless there anyway, plumbing runs)."""
    spec = _perfmodel.chip_spec(device_kind)
    return spec.hbm_gbps if spec else _DEFAULT_HBM_GBPS


def child() -> int:
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    platform = dev.platform
    hbm_gbps = _hbm_roofline_gbps(getattr(dev, "device_kind", ""))
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((E, F), np.float32) * 0.02,
                    jnp.bfloat16)
    a = jnp.asarray(rng.standard_normal((1, E), np.float32),
                    jnp.bfloat16)

    from theroundtaible_tpu.engine.models.common import (Int4Leaf,
                                                         dequant_int4)
    from theroundtaible_tpu.engine.quant import (_quantize_leaf,
                                                 _quantize_leaf_int4)

    q8 = _quantize_leaf(w, (1,), jnp.bfloat16, False)
    leaf = _quantize_leaf_int4(w, (1,), jnp.bfloat16, False, GROUP)
    assert isinstance(leaf, Int4Leaf)

    @jax.jit
    def f_bf16(a, w):
        return jnp.einsum("be,ef->bf", a, w,
                          preferred_element_type=jnp.float32)

    @jax.jit
    def f_int8(a, q, s):
        y = jnp.einsum("be,ef->bf", a, q.astype(a.dtype),
                       preferred_element_type=jnp.float32)
        return y * s.astype(jnp.float32)[None, :]

    @jax.jit
    def f_int4(a, q4, s4):
        w = dequant_int4(q4, s4, leaf.axis, leaf.group, a.dtype)
        return jnp.einsum("be,ef->bf", a, w,
                          preferred_element_type=jnp.float32)

    # native S4 storage: same values, stored as jnp.int4 (XLA packs)
    @jax.jit
    def to_s4(q4):
        pairs = jax.lax.bitcast_convert_type(q4, jnp.int4)
        return pairs.reshape(E, F)

    @jax.jit
    def f_s4(a, qs4, s4):
        w = qs4.astype(a.dtype).reshape(E, F // GROUP, GROUP) \
            * s4[..., None].astype(a.dtype)
        return jnp.einsum("be,ef->bf", a, w.reshape(E, F),
                          preferred_element_type=jnp.float32)

    def timed(name, fn, args, streamed_bytes, extra=None):
        """Each iteration's activation is perturbed by (prev_out · 0) so
        every dispatch DEPENDS on the previous one: window #2 measured
        physically impossible rates (head-bf16 "8.4 TB/s" vs the ~819
        GB/s HBM roofline) from the independent-repeat loop — under the
        axon tunnel, block_until_ready on the last of N independent
        dispatches does not reliably price the other N-1. The full
        decode bench never had this problem because token feedback
        chains its steps; this loop now chains the same way. The
        perturbation is folded INSIDE the jitted call so each iteration
        stays ONE dispatch (eager per-iter chaining ops would add
        dispatch overhead comparable to the ~20-60us GEMVs measured)."""

        @jax.jit
        def chained(prev, *a):
            a0 = a[0] + (prev.reshape(-1)[0] * 0).astype(a[0].dtype)
            return fn(a0, *a[1:])

        try:
            out = fn(*args)
            out = chained(out, *args)   # warm the chained compile
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = chained(out, *args)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / ITERS
            eff_gbps = streamed_bytes / dt / 1e9
            print(json.dumps({
                "variant": name, "platform": platform,
                "us_per_call": round(dt * 1e6, 1),
                "streamed_mb": round(streamed_bytes / 1e6, 2),
                "effective_gbps": round(eff_gbps, 1),
                # Shared-roofline attribution (ISSUE 6): fraction of
                # the chip's HBM bandwidth this variant achieved.
                "hbm_roofline_gbps": hbm_gbps,
                "roofline_frac": round(eff_gbps / hbm_gbps, 3),
                **(extra or {}),
            }), flush=True)
        except Exception as e:  # a variant crashing is itself the data
            print(json.dumps({"variant": name, "platform": platform,
                              "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)

    from theroundtaible_tpu.engine.pallas import int4mm

    @jax.jit
    def f_int4_kernel(a, q4, s4):
        y = int4mm.einsum_int4(
            "be,ef->bf", a,
            Int4Leaf(q4=q4, s4=s4, axis=leaf.axis, group=leaf.group))
        assert y is not None, "kernel declined MLP shape"
        return y

    def timed_kernel(name, fn, args, streamed_bytes, spec, a_shape,
                     klf):
        """Kernel variants carry PATH PROVENANCE (ISSUE 3): a shape the
        plan declines emits an explicit fallback_reason record instead
        of crashing the whole child — the window's numbers stay
        attributable either way."""
        reason = int4mm.plan_reason(spec, a_shape, klf)
        if reason:
            print(json.dumps({"variant": name, "platform": platform,
                              "path": "xla_dequant",
                              "fallback_reason": reason}), flush=True)
            return
        timed(name, fn, args, streamed_bytes,
              extra={"path": "pallas_w4a16"})

    # Kernel variants measure FIRST (window ordering, ISSUE 3): they are
    # the least-replaceable numbers — a child killed mid-run has already
    # landed the records the window exists for.
    i4_bytes = leaf.q4.size + leaf.s4.size * 2
    timed_kernel("int4-kernel", f_int4_kernel, (a, leaf.q4, leaf.s4),
                 i4_bytes, "be,ef->bf", (1, E), leaf)
    timed("bf16", f_bf16, (a, w), w.size * 2)
    timed("int8", f_int8, (a, q8["q"], q8["s"]),
          q8["q"].size + q8["s"].size * 2)
    timed("int4", f_int4, (a, leaf.q4, leaf.s4), i4_bytes)
    try:
        qs4 = to_s4(leaf.q4)
        jax.block_until_ready(qs4)
        timed("int4-s4", f_s4, (a, qs4, leaf.s4), i4_bytes)
    except Exception as e:
        print(json.dumps({"variant": "int4-s4", "platform": platform,
                          "error": f"{type(e).__name__}: {e}"[:300]}),
              flush=True)

    # lm-head shape: [V, E] with the CONTRACTED axis (E) packed — the
    # tied-embedding head is the single biggest per-token weight read
    # (0.78 ms/tok in the int8 hardware profile), and its dequant sits
    # on the opposite side of the contraction from the MLP case above.
    V = 32768  # structural stand-in for 256k (same fusion question)
    head = jnp.asarray(rng.standard_normal((V, E), np.float32) * 0.02,
                       jnp.bfloat16)
    h8 = _quantize_leaf(head, (0,), jnp.bfloat16, False)
    hleaf = _quantize_leaf_int4(head, (0,), jnp.bfloat16, False, GROUP)
    assert isinstance(hleaf, Int4Leaf)

    @jax.jit
    def h_bf16(a, w):
        return jnp.einsum("be,ve->bv", a, w,
                          preferred_element_type=jnp.float32)

    @jax.jit
    def h_int8(a, q, s):
        y = jnp.einsum("be,ve->bv", a, q.astype(a.dtype),
                       preferred_element_type=jnp.float32)
        return y * s.astype(jnp.float32)[None, :]

    @jax.jit
    def h_int4(a, q4, s4):
        w = dequant_int4(q4, s4, hleaf.axis, hleaf.group, a.dtype)
        return jnp.einsum("be,ve->bv", a, w,
                          preferred_element_type=jnp.float32)

    @jax.jit
    def h_int4_kernel(a, q4, s4):
        y = int4mm.einsum_int4(
            "be,ve->bv", a,
            Int4Leaf(q4=q4, s4=s4, axis=hleaf.axis, group=hleaf.group))
        assert y is not None, "kernel declined head shape"
        return y

    timed_kernel("head-int4-kernel", h_int4_kernel,
                 (a, hleaf.q4, hleaf.s4),
                 hleaf.q4.size + hleaf.s4.size * 2, "be,ve->bv", (1, E),
                 hleaf)
    timed("head-bf16", h_bf16, (a, head), head.size * 2)
    timed("head-int8", h_int8, (a, h8["q"], h8["s"]),
          h8["q"].size + h8["s"].size * 2)
    timed("head-int4", h_int4, (a, hleaf.q4, hleaf.s4),
          hleaf.q4.size + hleaf.s4.size * 2)
    return 0


def main() -> int:
    from bench_common import run_watchdogged

    return run_watchdogged(os.path.abspath(__file__), [],
                           ATTEMPT_TIMEOUT_S)


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else main())
