// Native runtime components for theroundtaible_tpu.
//
// The reference's operational system leans on llama.cpp's C++ for its local
// compute, including its GGUF weight loader (reference src/adapters/
// local-llm.ts reaches it over HTTP; SURVEY.md §2.3). The TPU build's
// compute is XLA, but the host-side runtime around it is native here:
//
//   st_convert  — checkpoint data-loader: mmap'd safetensors payload,
//                 multithreaded dtype conversion (bf16/f16 → f32) straight
//                 into caller-owned numpy buffers. Python parses the tiny
//                 JSON header; this does the gigabytes.
//   rt_lcp      — KV slot allocator primitive: longest common token prefix
//                 between a cached slot and an incoming prompt (the
//                 delta-prefill decision, engine/kvcache.py).
//
// Built as a plain shared library; bound via ctypes (no pybind11 in the
// image). Every entry point is C ABI.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// Matches theroundtaible_tpu/native/loader.py
enum DType : int32_t {
  DT_F32 = 0,
  DT_F16 = 1,
  DT_BF16 = 2,
  DT_F64 = 3,
  DT_I64 = 4,
  DT_I32 = 5,
  DT_U8 = 6,
  DT_I8 = 7,
};

struct TensorJob {
  uint64_t src_offset;  // byte offset of tensor data within the file
  uint64_t n_elems;
  int32_t src_dtype;
  int32_t pad;
  void* dst;  // caller-owned f32 (or i64/i32 passthrough) buffer
};

static inline float bf16_to_f32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float out;
  std::memcpy(&out, &bits, sizeof(out));
  return out;
}

static inline float f16_to_f32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t mant = h & 0x3FFu;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;  // ±0
    } else {        // subnormal: normalize. mant MSB at bit p gives
      int shift = 0;  // value (1.f)·2^(p-24) → biased f32 exp 103+p
      while (!(mant & 0x400u)) {
        mant <<= 1;
        ++shift;
      }
      mant &= 0x3FFu;
      bits = sign | ((127 - 15 + 1 - shift) << 23) | (mant << 13);
    }
  } else if (exp == 0x1F) {
    bits = sign | 0x7F800000u | (mant << 13);  // inf/nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &bits, sizeof(out));
  return out;
}

static void convert_range(const uint8_t* src, int32_t src_dtype, float* dst,
                          uint64_t begin, uint64_t end) {
  switch (src_dtype) {
    case DT_F32:
      std::memcpy(dst + begin, src + begin * 4, (end - begin) * 4);
      break;
    case DT_BF16: {
      const uint16_t* s = reinterpret_cast<const uint16_t*>(src);
      for (uint64_t i = begin; i < end; ++i) dst[i] = bf16_to_f32(s[i]);
      break;
    }
    case DT_F16: {
      const uint16_t* s = reinterpret_cast<const uint16_t*>(src);
      for (uint64_t i = begin; i < end; ++i) dst[i] = f16_to_f32(s[i]);
      break;
    }
    case DT_F64: {
      const double* s = reinterpret_cast<const double*>(src);
      for (uint64_t i = begin; i < end; ++i)
        dst[i] = static_cast<float>(s[i]);
      break;
    }
    case DT_I64: {
      const int64_t* s = reinterpret_cast<const int64_t*>(src);
      for (uint64_t i = begin; i < end; ++i)
        dst[i] = static_cast<float>(s[i]);
      break;
    }
    case DT_I32: {
      const int32_t* s = reinterpret_cast<const int32_t*>(src);
      for (uint64_t i = begin; i < end; ++i)
        dst[i] = static_cast<float>(s[i]);
      break;
    }
    case DT_U8: {
      for (uint64_t i = begin; i < end; ++i)
        dst[i] = static_cast<float>(src[i]);
      break;
    }
    case DT_I8: {
      const int8_t* s = reinterpret_cast<const int8_t*>(src);
      for (uint64_t i = begin; i < end; ++i)
        dst[i] = static_cast<float>(s[i]);
      break;
    }
  }
}

// Convert n_jobs tensors from the mmap'd safetensors payload into the
// caller's f32 buffers using n_threads workers. Large tensors are split
// across workers in ~4M-element slices. Returns 0 on success, negative
// errno-style codes on failure.
int st_convert(const char* path, const TensorJob* jobs, int64_t n_jobs,
               int32_t n_threads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -2;
  }
  size_t file_size = static_cast<size_t>(st.st_size);
  void* base = mmap(nullptr, file_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return -3;
  const uint8_t* data = static_cast<const uint8_t*>(base);

  static const uint64_t kElemSize[] = {4, 2, 2, 8, 8, 4, 1, 1};

  // Bounds-check every job before touching anything. Ordered so no
  // intermediate can wrap uint64 (a hostile header with a huge/negative
  // offset must fail here, not segfault in convert_range).
  for (int64_t j = 0; j < n_jobs; ++j) {
    const TensorJob& job = jobs[j];
    if (job.src_dtype < 0 || job.src_dtype > DT_I8) {
      munmap(base, file_size);
      return -4;
    }
    uint64_t elem = kElemSize[job.src_dtype];
    if (job.n_elems > file_size / elem ||
        job.src_offset > file_size - job.n_elems * elem) {
      munmap(base, file_size);
      return -4;
    }
  }

  // Work queue: (job index, begin, end) slices.
  struct Slice {
    int64_t job;
    uint64_t begin, end;
  };
  std::vector<Slice> slices;
  const uint64_t kChunk = 4u << 20;  // elements per slice
  for (int64_t j = 0; j < n_jobs; ++j) {
    for (uint64_t b = 0; b < jobs[j].n_elems; b += kChunk) {
      uint64_t e = b + kChunk < jobs[j].n_elems ? b + kChunk
                                                : jobs[j].n_elems;
      slices.push_back({j, b, e});
    }
  }

  std::atomic<size_t> next(0);
  auto worker = [&]() {
    for (;;) {
      size_t i = next.fetch_add(1);
      if (i >= slices.size()) return;
      const Slice& s = slices[i];
      const TensorJob& job = jobs[s.job];
      convert_range(data + job.src_offset, job.src_dtype,
                    static_cast<float*>(job.dst), s.begin, s.end);
    }
  };

  int nt = n_threads > 0 ? n_threads
                         : static_cast<int>(
                               std::thread::hardware_concurrency());
  if (nt < 1) nt = 1;
  if (static_cast<size_t>(nt) > slices.size()) nt = slices.size();
  std::vector<std::thread> threads;
  for (int t = 1; t < nt; ++t) threads.emplace_back(worker);
  worker();
  for (auto& th : threads) th.join();

  munmap(base, file_size);
  return 0;
}

// Longest common prefix of two int32 token sequences.
int64_t rt_lcp(const int32_t* a, int64_t n, const int32_t* b, int64_t m) {
  int64_t limit = n < m ? n : m;
  int64_t i = 0;
  while (i < limit && a[i] == b[i]) ++i;
  return i;
}

}  // extern "C"
