#!/bin/bash
# Round-4 hardware window — VERDICT r3 strict order (items 1 and 2):
#   1. bench.py          re-measure config 1 (pipelined decode segments +
#                        pool-direct paged prefill have no hardware number)
#   2. bench_profile.py  first-ever hardware decode attribution (the
#                        45%-of-roofline gap)
#   3. bench_discuss.py  config 2 — the north-star metric's first
#                        hardware number
#   4. bench_suite.py    configs 3-5 refresh (median-of-3 + spread now)
#
# Each bench is probe-first watchdogged (bench_common): a dead tunnel
# yields a machine-readable bench_status record instead of a hang, and
# every completed record streams into the artifact even if a later step
# dies. Artifacts are committed after EVERY step — the tunnel has died
# mid-round in rounds 2, 3, and (so far) 4.
set -u
cd "$(dirname "$0")" || exit 1
OUT=BENCH_r05_builder.jsonl
. ./hw_window_lib.sh

run_step "bench.py (config 1)"        python bench.py
run_step "bench_profile.py"           python bench_profile.py
run_step "bench_discuss.py (config 2)" python bench_discuss.py
run_step "bench_suite.py (configs 3-5)" python bench_suite.py all
# LAST + timeout-guarded: bench_realweights is not watchdogged (its CPU
# artifact is already committed) — on a live chip this serves the REAL
# trained checkpoint through discuss on TPU, but a mid-window tunnel
# death must not hang the window after the core four steps landed.
run_step "bench_realweights.py (on-chip)" \
  timeout 900 python bench_realweights.py --min-turns 20 --budget-s 840
git add REALWEIGHTS_r05.json 2>/dev/null && \
  git commit -q -o REALWEIGHTS_r05.json \
    -m "Hardware window: on-chip realweights artifact

No-Verification-Needed: measurement artifact only, no source change" \
  || true
echo "window complete: $(stamp)"; tail -n +1 "$OUT" | wc -l
